"""Fast lane: driver->C++ core->worker task path (zero daemon Python).

The native daemon core (``native/daemon_core.cc``) is the raylet-style
C++ engine for the per-task hot loop — lease a free worker, forward the
payload, pump the outcome back (reference:
``src/ray/raylet/node_manager.cc`` HandleRequestWorkerLease +
``raylet/local_task_manager.h`` dispatch). This module is everything
that speaks its wire protocol from Python:

- :class:`CoreHandle` — daemon side: start/stop the in-process C++
  event loop via ctypes.
- :class:`FastLaneClient` — driver side: submit plain tasks straight to
  the core (one frame out, one frame in; the Python daemon never sees
  them).
- :func:`worker_fast_lane_start` — worker side: a lane thread reading
  EXEC frames plus ONE persistent exec thread (no per-task thread
  spawn), replying RESULT frames.

Task payloads are msgpack maps (ids as raw bytes); results are the same
cloudpickle blobs the classic path ships. Only plain NORMAL tasks ride
the lane — actors, generators, runtime-env tasks keep the classic
daemon path, which stays the policy/compat surface.
"""

from __future__ import annotations

import ctypes
import itertools
import socket
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

import msgpack

from ray_tpu._private.lock_sanitizer import tracked_lock

from ray_tpu._private import failpoints as _fp
from ray_tpu._private import netchaos as _nc

# ops (mirror daemon_core.cc)
OP_HELLO_WORKER = 0x01
OP_SUBMIT = 0x02
OP_RESULT = 0x03
OP_CANCEL = 0x04
OP_PING = 0x05
OP_EXEC = 0x06
OP_REPLY = 0x07
OP_CANCEL_EXEC = 0x08
OP_HELLO_TAGGED = 0x09
OP_SUBMIT_TARGETED = 0x0A
OP_HELLO_ACK = 0x0B

KIND_OK = 0x00
KIND_ERR = 0x01
KIND_CRASHED = 0x63
KIND_CANCELLED = 0x64
KIND_PONG = 0x65
# LEGACY: the function returned a live generator and the worker asked
# the driver to re-run classically. No longer emitted — re-running a
# plain function whose body already ran doubled its side effects; the
# worker now drains and ships KIND_GEN_LIST instead. Drivers keep
# decoding it (classic replay) for old workers mid-upgrade.
KIND_GEN_FALLBACK = 0x66
# the callable returned a generator: the body already ran (actor state
# mutated / plain-function side effects done), so no re-run — the
# worker drains it and ships the item LIST; the driver replays it as a
# stream
KIND_GEN_LIST = 0x67

_U32 = struct.Struct("<I")
_U64 = struct.Struct("<Q")


# ONE recv implementation for every wire layer (recv_into, no per-chunk
# copies): rpc.recv_exact raises ConnectionError on EOF, which this
# module's except (ConnectionError, OSError) sites already handle.
from ray_tpu._private.rpc import SEND_CONCAT_MAX as _SEND_CONCAT_MAX
from ray_tpu._private.rpc import recv_exact as _recv_exact


def _read_frame(sock: socket.socket) -> bytearray:
    while True:
        (blen,) = _U32.unpack(_recv_exact(sock, 4))
        blob = _recv_exact(sock, blen)
        if (_nc.ENABLED
                and _nc.on_recv(sock, blen + 4) is _nc.DROP_FRAME):
            continue    # inbound lane frame lost on the simulated link
        return blob


def _frame_stream(sock: socket.socket):
    """Yield complete frames from a buffered reader: ONE recv may
    deliver many small frames (a drain storm's replies / a burst of
    EXEC frames), where per-frame recv_exact paid two syscalls per
    frame. Raises ConnectionError on EOF like recv_exact."""
    buf = bytearray()
    while True:
        off = 0
        n = len(buf)
        while n - off >= 4:
            (blen,) = _U32.unpack_from(buf, off)
            end = off + 4 + blen
            if end > n:
                break
            if (_nc.ENABLED
                    and _nc.on_recv(sock, blen + 4) is _nc.DROP_FRAME):
                off = end       # frame lost on the simulated link
                continue
            yield buf[off + 4:end]
            off = end
        if off:
            del buf[:off]
        chunk = sock.recv(1 << 18)
        if not chunk:
            raise ConnectionError("lane socket closed")
        buf += chunk


def _send_lane_frame(sock: socket.socket, wlock: threading.Lock, op: int,
                     head: bytes, payload: bytes = b"") -> None:
    """Lane frame write shared by client and worker sides: header and
    small payloads concatenate (one syscall); large payloads go as a
    second sendall under the same lock — no multi-MB concat copy."""
    prefix = _U32.pack(1 + len(head) + len(payload)) + bytes([op]) + head
    if _nc.ENABLED:
        verdict = _nc.on_send(sock, len(prefix) + len(payload))
        if verdict is _nc.DROP_FRAME:
            return      # whole frame suppressed; lane framing intact
        if verdict is _nc.DUP_FRAME:
            with wlock:
                if len(payload) <= _SEND_CONCAT_MAX:
                    sock.sendall(prefix + payload)
                else:
                    sock.sendall(prefix)
                    sock.sendall(payload)
    with wlock:
        if len(payload) <= _SEND_CONCAT_MAX:
            sock.sendall(prefix + payload)
        else:
            sock.sendall(prefix)
            sock.sendall(payload)


# ---------------------------------------------------------------------------
# daemon side: own the C++ core
# ---------------------------------------------------------------------------

class CoreHandle:
    """Loads the native core and runs it inside this process."""

    def __init__(self) -> None:
        from ray_tpu._private.native_build import load_native_so

        self._lib = load_native_so("daemon_core.cc",
                                   "libray_tpu_daemon_core.so")
        self.port: Optional[int] = None
        if self._lib is not None:
            self._lib.rtdc_start.restype = ctypes.c_int
            self._lib.rtdc_start.argtypes = [ctypes.c_char_p,
                                             ctypes.c_int]
            self._lib.rtdc_stats.argtypes = [
                ctypes.POINTER(ctypes.c_uint64)]

    def start(self, host: str = "0.0.0.0", port: int = 0) -> Optional[int]:
        if self._lib is None:
            return None
        got = self._lib.rtdc_start(host.encode(), port)
        self.port = got if got > 0 else None
        return self.port

    def stats(self) -> Dict[str, int]:
        if self._lib is None or self.port is None:
            return {}
        out = (ctypes.c_uint64 * 4)()
        self._lib.rtdc_stats(out)
        return {"queued": out[0], "inflight": out[1],
                "free_workers": out[2], "submitted": out[3]}

    def stop(self) -> None:
        if self._lib is not None and self.port is not None:
            self._lib.rtdc_stop()
            self.port = None


# ---------------------------------------------------------------------------
# driver side
# ---------------------------------------------------------------------------

class FastLaneError(Exception):
    """Transport failure on the fast lane (core/daemon died)."""


class FastLaneUnsubmitted(FastLaneError):
    """The frame provably never reached the wire (it was still staged
    when another thread's flush failed): nothing ran on the daemon, so
    callers fall back to the classic path without consuming a retry."""


# wait() sentinel for a slot whose frame was never written (distinct
# from None = lane died after the frame may have been delivered)
_UNSUBMITTED = object()


def replay_gen_list(blob: bytes):
    """Decode a KIND_GEN_LIST payload into a live generator replaying
    the worker-drained items — ONE decoder for every driver path
    (cluster handle + in-process router), so protocol changes can't
    drift between them. The body already ran worker-side; the driver's
    streaming machinery consumes the replay exactly like a classic
    stream without re-running anything."""
    import cloudpickle
    items = cloudpickle.loads(blob)

    def replay():
        yield from items

    return replay()


def lane_reconnect_policy():
    """The shared reconnect schedule for lane clients: a brief backoff
    window (the daemon may be mid-core-restart); persistent failure is
    the caller's cue to disable the lane."""
    from ray_tpu._private.retry import RetryPolicy
    return RetryPolicy(max_attempts=3, base_s=0.02, max_backoff_s=0.2)


class FastLaneClient:
    """One connection to a daemon's C++ core; thread-safe submit."""

    def __init__(self, addr: Tuple[str, int], link_id: str = "lane"):
        self._sock = socket.create_connection(addr, timeout=10.0)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock.settimeout(None)
        # default identity is the bare "lane"; the driver passes a
        # node-scoped id ("lane:<node_hex>") so a chaos spec can
        # partition ONE node's lane without touching its siblings
        _nc.register_link(self._sock, "daemon", link_id=link_id)
        self._wlock = tracked_lock("fast_lane.wire", reentrant=False)
        self._rids = itertools.count(1)
        # rid -> [Event, kind, payload]
        self._pending: Dict[int, list] = {}  #: guarded by self._plock
        self._plock = tracked_lock("fast_lane.pending", reentrant=False)
        # Flat-combining send stage: under concurrent submission the
        # lock holder drains everyone's frames with ONE sendall (a
        # drain storm paid a syscall + wire wakeup per task); an
        # uncontended send stays synchronous — same latency and error
        # surface as before.
        self._send_stage: list = []     #: guarded by self._stage_lock
        self._send_flushing = False     #: guarded by self._stage_lock
        self._stage_lock = tracked_lock("fast_lane.send_stage",
                                        reentrant=False)
        self.dead = False
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True, name="fastlane-read")
        self._reader.start()

    # -- wire -------------------------------------------------------------
    def _send(self, op: int, head: bytes, payload: bytes = b"",
              rid: Optional[int] = None) -> None:
        prefix = (_U32.pack(1 + len(head) + len(payload))
                  + bytes([op]) + head)
        if len(payload) > _SEND_CONCAT_MAX:
            # large frame: rides the stage as a TWO-PART entry so the
            # flusher writes it in FIFO position without a multi-MB
            # concat copy. Bypassing the stage (the old direct write
            # under _wlock) could overtake this thread's own earlier
            # staged frame — reordering two calls to one actor.
            frame = (prefix, payload)
        else:
            frame = prefix + payload
        with self._stage_lock:
            self._send_stage.append((frame, rid))
            if self._send_flushing:
                # a flusher is active: it picks this frame up in its
                # next pass. A flush failure there resolves this slot
                # by delivery state: still-staged frames come back
                # FastLaneUnsubmitted (classic fallback, no retry),
                # written-or-partial ones as lane death (retry
                # accounting) — same contract as post-submit loss.
                return
            self._send_flushing = True
        self._drain_send_stage(frame)

    def _drain_send_stage(self, own_frame=None) -> None:
        # A send failure raises to the caller ONLY while own_frame was
        # provably never delivered: it was the sole frame of the failed
        # write (sendall raising then guarantees the daemon can't hold
        # a complete frame). Any other failure splits by delivery
        # state: frames still staged (never written) resolve their
        # slots FastLaneUnsubmitted — their submitters take the classic
        # path retry-free — while frames in the failed or an earlier
        # write may have reached the daemon, so their slots fail as
        # lane death (wait() raises "died mid-call" -> retry
        # accounting). Raising for a possibly-delivered frame would
        # make the classic fallback re-run a task the daemon may
        # already be executing.
        while True:
            with self._stage_lock:
                batch = self._send_stage
                if not batch:
                    self._send_flushing = False
                    return
                self._send_stage = []
            try:
                with self._wlock:
                    self._write_batch(batch)
            except BaseException:
                with self._stage_lock:
                    unwritten = self._send_stage
                    self._send_stage = []
                    self._send_flushing = False
                self._resolve_unsubmitted(unwritten)
                self._fail_pending()
                if len(batch) == 1 and batch[0][0] is own_frame:
                    raise
                return
            if own_frame is not None and any(
                    f is own_frame for f, _ in batch):
                own_frame = None

    def _write_batch(self, batch) -> None:
        """Write staged entries in FIFO order (caller holds _wlock):
        consecutive small frames join into one sendall; a large
        two-part entry flushes the run, then writes prefix + payload
        without ever concatenating the big payload."""
        run: list = []
        for f, _ in batch:
            if _nc.ENABLED:
                nb = (len(f[0]) + len(f[1])) if isinstance(f, tuple) \
                    else len(f)
                verdict = _nc.on_send(self._sock, nb)
                if verdict is _nc.DROP_FRAME:
                    continue    # staged frame lost on the simulated link
                if verdict is _nc.DUP_FRAME:
                    if isinstance(f, tuple):
                        run.extend(f)
                    else:
                        run.append(f)
            if isinstance(f, tuple):
                if run:
                    self._sock.sendall(
                        run[0] if len(run) == 1 else b"".join(run))
                    run = []
                self._sock.sendall(f[0])
                self._sock.sendall(f[1])
            else:
                run.append(f)
        if run:
            self._sock.sendall(run[0] if len(run) == 1 else b"".join(run))

    def _resolve_unsubmitted(self, entries) -> None:
        """Slots of never-written frames: resolve as UNSUBMITTED before
        _fail_pending sweeps the rest as died-mid-call."""
        for _, rid in entries:
            if rid is None:
                continue
            with self._plock:
                slot = self._pending.pop(rid, None)
            if slot is not None:
                slot[1] = _UNSUBMITTED
                slot[0].set()

    def _fail_pending(self) -> None:
        self.dead = True
        with self._plock:
            pending, self._pending = self._pending, {}
        for slot in pending.values():
            slot[1] = None
            slot[0].set()

    def _read_loop(self) -> None:
        try:
            for body in _frame_stream(self._sock):
                if not body or body[0] != OP_REPLY or len(body) < 10:
                    continue
                (rid,) = _U64.unpack_from(body, 1)
                kind = body[9]
                blob = body[10:]
                with self._plock:
                    slot = self._pending.pop(rid, None)
                if slot is not None:
                    slot[1] = kind
                    slot[2] = blob
                    slot[0].set()
        except (ConnectionError, OSError):
            pass
        self._fail_pending()

    # -- API --------------------------------------------------------------
    def submit(self, payload: bytes) -> Tuple[int, list]:
        """Send a task payload; returns (rid, slot) to wait on."""
        return self._submit_op(OP_SUBMIT, b"", payload)

    def submit_targeted(self, tag: int,
                        payload: bytes) -> Tuple[int, list]:
        """Send to the TAGGED worker (per-actor FIFO ordering)."""
        return self._submit_op(OP_SUBMIT_TARGETED, _U64.pack(tag),
                               payload)

    def _submit_op(self, op: int, extra: bytes,
                   payload: bytes) -> Tuple[int, list]:
        if self.dead:
            raise FastLaneError("fast lane is down")
        rid = next(self._rids)
        slot = [threading.Event(), None, None]
        with self._plock:
            self._pending[rid] = slot
        try:
            # DROP surfaces as a send failure: the lane is a stream
            # socket, so a lost frame desyncs framing — peers treat it
            # as connection loss, and the caller's classic fallback
            # stays safe (nothing was submitted)
            if _fp.ENABLED and _fp.fire("fast_lane.submit",
                                        op=op) is _fp.DROP:
                raise OSError("frame dropped by failpoint")
            self._send(op, _U64.pack(rid) + extra, payload, rid=rid)
        except Exception as e:  # noqa: BLE001 — any send-path failure
            # (socket death OR an injected error of any class) must pop
            # the slot and mark the lane dead; a narrower catch leaked
            # one pending slot per escape
            self.dead = True
            with self._plock:
                self._pending.pop(rid, None)
            raise FastLaneError(str(e))
        return rid, slot

    def wait(self, slot: list,
             timeout: Optional[float] = None) -> Tuple[int, bytes]:
        # Same loop-affinity contract as AsyncClient.call: the lane
        # reply arrives on a reader thread, but blocking the process
        # event loop here would stall every peer the loop serves —
        # fail loudly instead of deadlocking quietly (async core).
        from ray_tpu._private import eventloop
        if eventloop.on_loop():
            raise RuntimeError(
                "FastLaneClient.wait would block the event loop; "
                "fast-lane round-trips belong on worker/caller threads")
        if not slot[0].wait(timeout):
            raise TimeoutError("fast lane reply timed out")
        if slot[1] is _UNSUBMITTED:
            raise FastLaneUnsubmitted(
                "frame never reached the wire (flush failed first)")
        if slot[1] is None:
            raise FastLaneError("fast lane died mid-call")
        return slot[1], slot[2]

    def cancel(self, rid: int, force: bool = False) -> None:
        try:
            self._send(OP_CANCEL,
                       _U64.pack(rid) + bytes([1 if force else 0]))
        except OSError:
            pass

    def ping(self, timeout: float = 5.0) -> Dict[str, int]:
        # mirrors _submit_op: a send failure must pop the pending slot
        # and mark the lane dead, not leak the slot and surface a raw
        # OSError into daemon stats paths
        if _fp.ENABLED:
            try:
                if _fp.fire("fast_lane.ping") is _fp.DROP:
                    raise OSError("ping dropped by failpoint")
            except Exception as e:  # noqa: BLE001 — any injected class
                # must mark the lane dead and surface as the typed
                # error, mirroring _submit_op's broadened catch
                self.dead = True
                raise FastLaneError(str(e))
        rid, slot = self._submit_op(OP_PING, b"", b"")
        kind, blob = self.wait(slot, timeout)
        if kind != KIND_PONG or len(blob) < 32:
            raise FastLaneError("bad pong")
        q, inf, w, done = struct.unpack("<QQQQ", blob[:32])
        return {"queued": q, "inflight": inf, "workers": w,
                "completed": done}

    def close(self) -> None:
        self.dead = True
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# worker side
# ---------------------------------------------------------------------------

def build_payload(spec, fid: str, args_blob: bytes, job_id,
                  node_id) -> bytes:
    """Driver-side: the msgpack task payload the worker lane decodes.
    Everything the worker's execution context needs travels here — the
    daemon's Python never synthesizes it (classic path:
    ``WorkerClient._ctx_fields``)."""
    return msgpack.packb({
        "fid": fid,
        "args": args_blob,
        "job": job_id.binary() if job_id is not None else b"",
        "task": spec.task_id.binary(),
        "node": node_id.binary() if node_id is not None else b"",
        "name": spec.name or "",
        "res": {k: float(v) for k, v in (spec.resources or {}).items()},
        "pg": (spec.placement_group_id.binary()
               if spec.placement_group_id is not None else b""),
        "pgc": bool(getattr(spec, "pg_capture", False)),
    }, use_bin_type=True)


def build_actor_payload(spec, args_blob: bytes, job_id,
                        node_id) -> bytes:
    """Driver-side payload for a TARGETED actor-method call."""
    return msgpack.packb({
        "method": spec.method_name,
        "args": args_blob,
        "job": job_id.binary() if job_id is not None else b"",
        "task": spec.task_id.binary(),
        "node": node_id.binary() if node_id is not None else b"",
        "aid": (spec.actor_id.binary()
                if spec.actor_id is not None else b""),
        "name": spec.name or "",
        "res": {k: float(v) for k, v in (spec.resources or {}).items()},
        "pg": (spec.placement_group_id.binary()
               if spec.placement_group_id is not None else b""),
        "pgc": bool(getattr(spec, "pg_capture", False)),
    }, use_bin_type=True)


# worker-side drain bound for generator-returning callables: the lane
# ships the drained items as ONE reply frame, so an unbounded (or
# infinite) generator must error out instead of wedging the lane worker
# / materializing gigabytes — true streaming belongs to the classic
# path (num_returns="streaming" or a generator function)
GEN_DRAIN_MAX_ITEMS = 100_000


def _drain_capped(gen) -> list:
    items: list = []
    for item in gen:
        items.append(item)
        if len(items) > GEN_DRAIN_MAX_ITEMS:
            gen.close()
            raise RuntimeError(
                f"fast-lane task returned a generator exceeding "
                f"{GEN_DRAIN_MAX_ITEMS} items; use "
                f"num_returns='streaming' (or a generator function) "
                f"for unbounded streams")
    return items


def worker_fast_lane_start(addr: Tuple[str, int], state,
                           tag: Optional[int] = None) -> None:
    """Connect this worker process to the core and serve EXEC frames.

    One lane thread reads frames; one persistent exec thread runs tasks
    (no per-task thread creation — at 3k tasks/s a 60us thread spawn is
    20% of the budget). CANCEL_EXEC async-raises KeyboardInterrupt into
    the exec thread, same soft-cancel contract as the classic path.

    With ``tag`` the worker registers TARGETED (per-actor lane): the
    core routes only submits addressed to this tag, strictly FIFO, and
    the exec thread runs them as ACTOR METHOD calls on
    ``state.actor_instance`` under the worker's actor lock (so classic
    streaming calls on the mp channel stay serialized with lane
    calls)."""
    import os  # noqa: F401 — force-cancel path

    sock = socket.create_connection(addr, timeout=10.0)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    sock.settimeout(None)
    _nc.register_link(sock, "daemon", link_id="lane")
    wlock = threading.Lock()

    def send(op: int, head: bytes, payload: bytes = b"") -> None:
        _send_lane_frame(sock, wlock, op, head, payload)

    if tag is not None:
        send(OP_HELLO_TAGGED, _U64.pack(tag))
        # wait for the core's ack: only then is the tag routable, so
        # the daemon's create-actor reply (and the driver's first
        # targeted submit) cannot outrun the registration
        body = _read_frame(sock)
        if not body or body[0] != OP_HELLO_ACK:
            raise RuntimeError("targeted lane hello not acknowledged")
    else:
        send(OP_HELLO_WORKER, b"")

    import queue as _q
    tasks: "_q.Queue[Optional[Tuple[int, dict]]]" = _q.Queue()
    current = {"tid": 0}
    exec_thread_holder = {}

    # hot-path imports resolved ONCE per worker, not per task
    import inspect

    import cloudpickle

    from ray_tpu._private import runtime_context
    from ray_tpu._private.ids import (ActorID, JobID, NodeID,
                                      PlacementGroupID, TaskID)
    from ray_tpu._private.worker_process import (_current_rid, _dump_exc,
                                                 _safe_dumps)

    def run_one(tid: int, msg: dict) -> None:
        current["tid"] = tid
        _current_rid.rid = f"fl{tid}"
        try:
            ctx = {
                "job_id": (JobID(msg["job"]) if msg["job"] else None),
                "task_id": TaskID(msg["task"]),
                "node_id": (NodeID(msg["node"])
                            if msg["node"] else None),
                "actor_id": (ActorID(msg["aid"])
                             if msg.get("aid") else None),
                "resources": msg["res"],
                "task_name": msg["name"],
                "placement_group_id": (
                    PlacementGroupID(msg["pg"])
                    if msg["pg"] else None),
                "pg_capture": msg["pgc"],
            }
            gen_items = None
            token = runtime_context._set_context(**ctx)
            try:
                args, kwargs = cloudpickle.loads(msg["args"])
                if "method" in msg:
                    # targeted actor call: run on the live instance,
                    # serialized with classic-path calls by the actor
                    # lock (ordering: the core's per-tag FIFO). A
                    # generator result drains HERE — still inside the
                    # runtime context and the lock, so the body sees
                    # its actor/task context and no other method
                    # interleaves with it.
                    lock = getattr(state, "actor_lock", None)
                    method = getattr(state.actor_instance,
                                     msg["method"])
                    if lock is not None:
                        with lock:
                            result = method(*args, **kwargs)
                            if inspect.isgenerator(result):
                                gen_items = _drain_capped(result)
                    else:
                        result = method(*args, **kwargs)
                        if inspect.isgenerator(result):
                            gen_items = _drain_capped(result)
                else:
                    fn = state._fn({"fn_id": msg["fid"]})
                    result = fn(*args, **kwargs)
                    if inspect.isgenerator(result):
                        # a PLAIN function returned a live generator:
                        # its body already ran (side effects included),
                        # so the lane must NOT hand the task back for a
                        # classic re-run (KIND_GEN_FALLBACK re-executed
                        # the body). Drain here — inside the runtime
                        # context — and ship the item list; the driver
                        # replays it as a stream. Generator FUNCTIONS
                        # never ride the lane (driver eligibility), so
                        # draining only ever covers already-run bodies.
                        gen_items = _drain_capped(result)
            finally:
                runtime_context._reset_context(token)
            if gen_items is not None:
                # the body already ran (actor method or plain function
                # that returned a generator) — ship the drained items;
                # the driver replays them as a stream
                state._flush_metrics()
                current["tid"] = 0
                blob = _safe_dumps(gen_items)
                try:
                    send(OP_RESULT,
                         _U64.pack(tid) + bytes([KIND_GEN_LIST]),
                         blob)
                except BaseException:  # noqa: BLE001 — partial frame
                    raise SystemExit from None
                return
            state._flush_metrics()
            # clear BEFORE the send: once the driver sees the result a
            # late CANCEL_EXEC must become a no-op, not an async
            # interrupt landing on the next task
            current["tid"] = 0
            blob = _safe_dumps(result)
            try:
                send(OP_RESULT, _U64.pack(tid) + bytes([KIND_OK]), blob)
            except BaseException:  # noqa: BLE001 — see below
                # ANY failure mid-send (socket error, late async
                # cancel) may leave a partial frame on the wire; the
                # stream is unrecoverable — exit so the core crashes
                # the task and the daemon respawns the worker
                raise SystemExit from None
        except SystemExit:
            raise
        except BaseException as e:  # noqa: BLE001 — shipped back
            try:
                state._flush_metrics()
                current["tid"] = 0
                send(OP_RESULT, _U64.pack(tid) + bytes([KIND_ERR]),
                     _dump_exc(e))
            except BaseException:  # noqa: BLE001 — same partial-frame risk
                raise SystemExit from None
        finally:
            current["tid"] = 0
            _current_rid.rid = None

    def exec_loop() -> None:
        while True:
            try:
                item = tasks.get()
                if item is None:
                    return
                run_one(*item)
            except SystemExit:
                return
            except KeyboardInterrupt:
                # a cancel's async-raise landed outside the task body
                # (late delivery): swallow it — the lane worker must
                # survive, not die holding the core's free slot
                continue

    def lane_loop() -> None:
        try:
            for body in _frame_stream(sock):
                if not body:
                    continue
                op = body[0]
                if op == OP_EXEC and len(body) >= 9:
                    (tid,) = _U64.unpack_from(body, 1)
                    msg = msgpack.unpackb(body[9:], raw=False)
                    tasks.put((tid, msg))
                elif op == OP_CANCEL_EXEC and len(body) >= 9:
                    (tid,) = _U64.unpack_from(body, 1)
                    force = len(body) >= 10 and body[9] == 1
                    if current["tid"] == tid:
                        if force:
                            # classic force-cancel contract: kill the
                            # worker; the core reports CRASHED and the
                            # driver maps a cancelled crash to
                            # TaskCancelledError
                            os._exit(1)
                        t = exec_thread_holder.get("t")
                        if t is not None and t.is_alive():
                            ctypes.pythonapi.PyThreadState_SetAsyncExc(
                                ctypes.c_ulong(t.ident),
                                ctypes.py_object(KeyboardInterrupt))
        except (ConnectionError, OSError):
            pass
        tasks.put(None)

    et = threading.Thread(target=exec_loop, daemon=True,
                          name="fastlane-exec")
    exec_thread_holder["t"] = et
    et.start()
    lt = threading.Thread(target=lane_loop, daemon=True,
                          name="fastlane-read")
    lt.start()
