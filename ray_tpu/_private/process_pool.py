"""Process worker pool: real OS-process task execution.

Reference: the raylet's WorkerPool (`raylet/worker_pool.h` —
StartWorkerProcess/PopWorker/prestart, SURVEY.md §8.6): tasks execute in
separate worker PROCESSES (isolation, true parallelism, crash = worker
failure not cluster failure). Opt-in here
(`ray_tpu.init(use_process_workers=True)`): NORMAL tasks with picklable
payloads route to pooled subprocess workers; actors and unpicklable
closures stay on the in-process thread path.

Workers are prestarted (reference: PrestartWorkers RPC) and recycled
across tasks; a crashed worker surfaces as a retryable system failure.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import threading
import traceback
from typing import Any, List, Optional, Tuple

import cloudpickle


class WorkerCrashed(Exception):
    pass


def _worker_main(conn) -> None:
    """Subprocess loop: receive (fn, args, kwargs) blobs, reply results."""
    while True:
        try:
            msg = conn.recv_bytes()
        except (EOFError, OSError):
            return
        if msg == b"__exit__":
            return
        try:
            fn, args, kwargs = cloudpickle.loads(msg)
            result = fn(*args, **kwargs)
            payload = cloudpickle.dumps(("ok", result))
        except BaseException as e:  # noqa: BLE001
            try:
                payload = cloudpickle.dumps(
                    ("err", e, traceback.format_exc()))
            except Exception:
                payload = cloudpickle.dumps(
                    ("err", RuntimeError(repr(e)),
                     traceback.format_exc()))
        try:
            conn.send_bytes(payload)
        except (BrokenPipeError, OSError):
            return


class _PooledWorker:
    def __init__(self, ctx):
        self.parent_conn, child_conn = ctx.Pipe()
        self.proc = ctx.Process(target=_worker_main, args=(child_conn,),
                                daemon=True)
        self.proc.start()
        child_conn.close()

    def run(self, fn, args, kwargs) -> Any:
        blob = cloudpickle.dumps((fn, args, kwargs))
        try:
            self.parent_conn.send_bytes(blob)
            payload = self.parent_conn.recv_bytes()
        except (EOFError, BrokenPipeError, OSError):
            raise WorkerCrashed(
                f"worker process {self.proc.pid} died "
                f"(exitcode={self.proc.exitcode})")
        out = cloudpickle.loads(payload)
        if out[0] == "ok":
            return out[1]
        _, err, tb = out
        raise err

    def alive(self) -> bool:
        return self.proc.is_alive()

    def stop(self) -> None:
        try:
            self.parent_conn.send_bytes(b"__exit__")
        except (BrokenPipeError, OSError):
            pass
        self.proc.join(timeout=1)
        if self.proc.is_alive():
            self.proc.terminate()
        try:
            self.parent_conn.close()
        except OSError:
            pass


class ProcessWorkerPool:
    """Fixed-size pool with prestart and crash replacement."""

    def __init__(self, size: int = 0, prestart: bool = True):
        # fork is the cheap path on Linux; worker children only unpickle
        # and run user fns (reference workers fork from a clean template
        # for the same reason).
        self._ctx = mp.get_context("fork")
        self.size = size or max(2, (os.cpu_count() or 4) // 2)
        self._idle: List[_PooledWorker] = []
        self._lock = threading.Lock()
        self._spawned = 0
        self._closed = False
        if prestart:
            for _ in range(self.size):
                self._idle.append(self._spawn())

    def _spawn(self) -> _PooledWorker:
        self._spawned += 1
        return _PooledWorker(self._ctx)

    def _checkout(self) -> _PooledWorker:
        with self._lock:
            while self._idle:
                w = self._idle.pop()
                if w.alive():
                    return w
                w.stop()
        return self._spawn()

    def _checkin(self, worker: _PooledWorker) -> None:
        with self._lock:
            if self._closed or not worker.alive() \
                    or len(self._idle) >= self.size:
                worker.stop()
                return
            self._idle.append(worker)

    def execute(self, fn, args, kwargs) -> Any:
        """Run fn in a pooled subprocess (blocking the calling thread —
        which is a node worker thread, so the resource model is
        unchanged). Raises WorkerCrashed on worker death."""
        worker = self._checkout()
        try:
            result = worker.run(fn, args, kwargs)
        except WorkerCrashed:
            worker.stop()
            raise
        self._checkin(worker)
        return result

    def stats(self):
        with self._lock:
            return {"idle": len(self._idle), "spawned": self._spawned}

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            idle, self._idle = self._idle, []
        for w in idle:
            w.stop()


def payload_is_picklable(fn, args, kwargs) -> bool:
    try:
        cloudpickle.dumps((fn, args, kwargs))
        return True
    except Exception:
        return False
