"""Cluster-level scheduling policies.

Parity contract (reference ``src/ray/raylet/scheduling/policy/``): hybrid
top-k (pack up to a utilization threshold, then spread), SPREAD, node
affinity (hard/soft), node-label selection, and placement-group bundle
placement. The two-level split of the reference (cluster pick + local
dispatch) is preserved: this module only picks a node; admission happens in
the node's dispatch loop (:mod:`ray_tpu._private.node`).
"""

from __future__ import annotations

import random
import threading
from typing import Any, Dict, List, Optional

from ray_tpu._private.node import Node
from ray_tpu._private.task_spec import (
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    TaskSpec,
)

# Hybrid policy knobs (reference: hybrid_scheduling_policy.h:29-50 —
# scheduler_spread_threshold, top-k fraction).
SPREAD_THRESHOLD = 0.5
TOP_K_FRACTION = 0.2

# ---------------------------------------------------------------------------
# Cluster epoch: a process-wide version of cluster MEMBERSHIP + static
# capacity. Bumped on node add/remove/drain and on placement-group
# bundle capacity changes — everything can_fit_total() depends on. The
# feasibility cache below keys on it, so a burst of identically-shaped
# submissions scans all nodes once per epoch instead of once per task.
# ---------------------------------------------------------------------------

_EPOCH = 0
_EPOCH_LOCK = threading.Lock()


def bump_cluster_epoch() -> int:
    """Invalidate cached feasibility (node add/remove/drain, capacity
    change). Cheap and safe to over-call."""
    global _EPOCH
    with _EPOCH_LOCK:
        _EPOCH += 1
        return _EPOCH


def cluster_epoch() -> int:
    return _EPOCH


class SchedulingError(Exception):
    """Task is infeasible: no alive node can ever satisfy it."""


def _soft_excluded(n: Any) -> bool:
    """Alive but taking no NEW placements: DRAINING (graceful
    preemption, PR 2) or HARD memory pressure (the node is shedding
    load — docs/fault_tolerance.md "Memory pressure & graceful
    degradation"). Both are soft: when every alive node is excluded,
    callers fall back to them — running somewhere beats failing a
    feasible demand."""
    return bool(getattr(n, "draining", False)
                or getattr(n, "pressure_level", "ok") == "hard")


_INFEASIBLE = object()      # negative-cache sentinel
_FEAS_CACHE_MAX = 512       # distinct resource shapes per epoch


class ClusterScheduler:
    def __init__(self):
        from ray_tpu._private.lock_sanitizer import tracked_lock
        self._lock = tracked_lock("scheduler", reentrant=False)
        self._spread_rr = 0  #: guarded by self._lock
        # (resource-shape, cluster-epoch) -> feasible candidate nodes
        self._feas_cache: Dict[tuple, Any] = {}  #: guarded by self._lock
        self._feas_epoch = -1                    #: guarded by self._lock
        # Fair-share consult (set by the runtime when `fairshare` is
        # on): over-cap jobs spread their queued work instead of
        # packing, so per-node quota gates free uniformly and one
        # node's backlog never pins a throttled job's whole deficit.
        self.tenancy = None

    def pick_node(self, spec: TaskSpec, nodes: List[Node],
                  preferred: Optional[Node] = None) -> Optional[Node]:
        """Choose a node for the task, or None if feasible-but-busy.

        Raises SchedulingError if no node can ever fit the demand.
        """
        strategy = spec.scheduling_strategy
        if (strategy == "DEFAULT" and self.tenancy is not None
                and self.tenancy.prefers_spread(
                    spec.job_id.hex() if spec.job_id is not None
                    else "")):
            # feasibility caching below is keyed on resource shape
            # only, so demoting pack->spread here cannot pollute the
            # cached candidate sets
            strategy = "SPREAD"
        if strategy == "DEFAULT" or strategy == "SPREAD":
            # hot path: plain strategies share one feasibility scan per
            # (resource shape, cluster epoch) — a burst of identical
            # specs does not re-scan every node per task
            feasible = self._feasible_cached(spec, nodes)
            if strategy == "SPREAD":
                return self._pick_spread(spec, feasible)
            return self._pick_hybrid(spec, feasible, preferred)

        alive = [n for n in nodes if n.alive]
        if not alive:
            raise SchedulingError("no alive nodes in cluster")
        # DRAINING and HARD-pressure nodes take no NEW placements while
        # their running work finishes / pressure relieves. When every
        # alive node is excluded, fall back to them — running the task
        # somewhere beats failing a feasible demand.
        schedulable = [n for n in alive if not _soft_excluded(n)] or alive

        if isinstance(strategy, PlacementGroupSchedulingStrategy):
            return self._pick_pg(spec, strategy, alive)
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            return self._pick_affinity(spec, strategy, alive, schedulable)
        if isinstance(strategy, NodeLabelSchedulingStrategy):
            # filter BOTH pools: the draining fallback below must never
            # widen past the label selector, and a selector whose only
            # match is draining still runs there rather than failing
            alive = self._filter_labels(strategy, alive)
            if not alive:
                raise SchedulingError("no node matches label selector")
            schedulable = [n for n in alive
                           if not _soft_excluded(n)] or alive
            strategy = "DEFAULT"

        feasible = self._compute_feasible(spec, alive, schedulable)
        if strategy == "SPREAD":
            return self._pick_spread(spec, feasible)
        return self._pick_hybrid(spec, feasible, preferred)

    # -- feasibility cache -------------------------------------------------
    @staticmethod
    def _compute_feasible(spec: TaskSpec, alive: List[Node],
                          schedulable: List[Node]) -> List[Node]:
        feasible = [n for n in schedulable
                    if n.ledger.can_fit_total(spec.resources)]
        if not feasible:
            # a demand only a draining node can hold still runs there
            # (letting it fail while capacity exists would be a loss)
            feasible = [n for n in alive
                        if n.ledger.can_fit_total(spec.resources)]
        if not feasible:
            raise SchedulingError(
                f"resource demand {spec.resources} is infeasible on every "
                f"alive node")
        return feasible

    def _feasible_cached(self, spec: TaskSpec,
                         nodes: List[Node]) -> List[Node]:
        epoch = _EPOCH
        key = tuple(sorted(spec.resources.items()))
        with self._lock:
            if self._feas_epoch != epoch:
                self._feas_cache.clear()
                self._feas_epoch = epoch
                entry = None
            else:
                entry = self._feas_cache.get(key)
        if entry is _INFEASIBLE:
            raise SchedulingError(
                f"resource demand {spec.resources} is infeasible on every "
                f"alive node")
        if entry is not None:
            # epoch bumps cover membership/drain transitions; this cheap
            # re-check makes a missed bump degrade to a recompute
            # instead of a placement on a dead/draining node
            live = [n for n in entry
                    if n.alive and not _soft_excluded(n)]
            if len(live) == len(entry):
                return entry
        alive = [n for n in nodes if n.alive]
        if not alive:
            raise SchedulingError("no alive nodes in cluster")
        schedulable = [n for n in alive
                       if not _soft_excluded(n)] or alive
        try:
            feasible = self._compute_feasible(spec, alive, schedulable)
        except SchedulingError:
            self._feas_store(epoch, key, _INFEASIBLE)
            raise
        # only cache clean candidate sets: a draining-/pressure-
        # fallback pick must re-evaluate per task (the fallback is a
        # last resort, not a steady state)
        if all(not _soft_excluded(n) for n in feasible):
            self._feas_store(epoch, key, feasible)
        return feasible

    def _feas_store(self, epoch: int, key: tuple, value: Any) -> None:
        with self._lock:
            if self._feas_epoch != epoch or _EPOCH != epoch:
                return      # the cluster moved underneath the scan
            if len(self._feas_cache) >= _FEAS_CACHE_MAX:
                self._feas_cache.clear()
            self._feas_cache[key] = value

    # -- policies ----------------------------------------------------------
    def _pick_hybrid(self, spec: TaskSpec, feasible: List[Node],
                     preferred: Optional[Node]) -> Optional[Node]:
        """Pack onto low-utilization nodes first; break ties toward preferred
        (locality) node; randomize among top-k to avoid herding."""
        scored = []
        for n in feasible:
            avail = n.effective_available()
            if not all(avail.get(k, 0.0) >= v - 1e-9
                       for k, v in spec.resources.items()):
                continue
            util = self._utilization(n)
            bias = -0.1 if (preferred is not None
                            and n.node_id == preferred.node_id) else 0.0
            scored.append((util + bias, n))
        if not scored:
            # All feasible nodes currently busy: queue on the least loaded
            # (its dispatch loop admits when resources free up). This mirrors
            # the reference's lease-queuing on the selected raylet.
            return min(feasible, key=self._utilization)
        scored.sort(key=lambda t: t[0])
        if scored[0][0] <= SPREAD_THRESHOLD:
            k = max(1, int(len(scored) * TOP_K_FRACTION))
            return random.choice(scored[:k])[1]
        return scored[0][1]

    def _pick_spread(self, spec: TaskSpec, feasible: List[Node]) -> Node:
        with self._lock:
            self._spread_rr += 1
            start = self._spread_rr
        # Prefer a currently-available node in round-robin order.
        order = [feasible[(start + i) % len(feasible)]
                 for i in range(len(feasible))]
        for n in order:
            avail = n.effective_available()
            if all(avail.get(k, 0.0) >= v - 1e-9
                   for k, v in spec.resources.items()):
                return n
        return order[0]

    def _pick_affinity(self, spec: TaskSpec,
                       strategy: NodeAffinitySchedulingStrategy,
                       alive: List[Node],
                       schedulable: Optional[List[Node]] = None) -> Node:
        if schedulable is None:
            schedulable = alive
        target = None
        for n in alive:
            if n.node_id.hex() == strategy.node_id:
                target = n
                break
        if target is not None and target.ledger.can_fit_total(spec.resources):
            # hard pins still land on a draining target (the user chose
            # the node); soft affinity prefers somewhere that will live
            if not (strategy.soft and getattr(target, "draining", False)):
                return target
        if strategy.soft:
            return self._pick_hybrid(spec, [
                n for n in schedulable
                if n.ledger.can_fit_total(spec.resources)
            ] or schedulable, None)
        raise SchedulingError(
            f"node {strategy.node_id[:8]} is dead or cannot fit "
            f"{spec.resources} (hard affinity)")

    def _filter_labels(self, strategy: NodeLabelSchedulingStrategy,
                       alive: List[Node]) -> List[Node]:
        def matches(node: Node, selector: Dict) -> bool:
            for key, expected in (selector or {}).items():
                actual = node.labels.get(key)
                if isinstance(expected, (list, tuple, set)):
                    if actual not in expected:
                        return False
                elif actual != expected:
                    return False
            return True

        hard = [n for n in alive if matches(n, strategy.hard)]
        if strategy.soft:
            soft = [n for n in hard if matches(n, strategy.soft)]
            if soft:
                return soft
        return hard

    def _pick_pg(self, spec: TaskSpec,
                 strategy: PlacementGroupSchedulingStrategy,
                 alive: List[Node]) -> Node:
        pg = strategy.placement_group
        if not pg.is_ready():
            raise SchedulingError(
                "placement group is not ready (wait on pg.ready() first)")
        idx = strategy.placement_group_bundle_index
        candidates = (pg.bundle_nodes() if idx == -1
                      else [pg.bundle_nodes()[idx]])
        node_by_id = {n.node_id: n for n in alive}
        fallback = None
        for node_id in candidates:
            n = node_by_id.get(node_id)
            if n is not None and n.ledger.can_fit_total(spec.resources):
                if getattr(n, "draining", False):
                    # bundle pinned to a draining node: use it only when
                    # no other bundle fits (the PG re-places on the
                    # node's eventual death)
                    fallback = fallback or n
                    continue
                return n
        if fallback is not None:
            return fallback
        raise SchedulingError(
            "no bundle in the placement group can fit the task")

    @staticmethod
    def _utilization(node: Node) -> float:
        total = node.ledger.total
        avail = node.effective_available()
        utils = [1.0 - avail.get(k, 0.0) / v
                 for k, v in total.items() if v > 0]
        return max(utils) if utils else 0.0
