"""Per-task runtime context (reference: python/ray/runtime_context.py)."""

from __future__ import annotations

import contextvars
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

_ctx: contextvars.ContextVar = contextvars.ContextVar("ray_tpu_ctx",
                                                      default=None)


@dataclass
class _TaskContext:
    job_id: Any = None
    task_id: Any = None
    node_id: Any = None
    actor_id: Any = None
    task_name: str = ""
    resources: Dict[str, float] = field(default_factory=dict)
    placement_group_id: Any = None
    pg_capture: bool = False  # placement_group_capture_child_tasks
    trace: Optional[Dict[str, Any]] = None  # distributed trace context


def _set_context(**kwargs):
    return _ctx.set(_TaskContext(**kwargs))


def _reset_context(token) -> None:
    try:
        _ctx.reset(token)
    except ValueError:
        # Context transfer across threads (async actor paths): best-effort.
        _ctx.set(None)


class RuntimeContext:
    """User-facing view of the current execution context."""

    @property
    def _task_ctx(self) -> Optional[_TaskContext]:
        return _ctx.get()

    def _runtime(self):
        from ray_tpu._private import worker
        return worker.global_worker()

    def get_job_id(self) -> str:
        return self._runtime().job_id.hex()

    def get_task_id(self) -> Optional[str]:
        c = self._task_ctx
        return c.task_id.hex() if c and c.task_id else None

    def get_task_name(self) -> Optional[str]:
        c = self._task_ctx
        return c.task_name if c else None

    def get_actor_id(self) -> Optional[str]:
        c = self._task_ctx
        return c.actor_id.hex() if c and c.actor_id else None

    def get_node_id(self) -> str:
        c = self._task_ctx
        if c and c.node_id:
            return c.node_id.hex()
        return self._runtime().head_node().node_id.hex()

    def get_assigned_resources(self) -> Dict[str, float]:
        c = self._task_ctx
        return dict(c.resources) if c else {}

    @property
    def namespace(self) -> str:
        return self._runtime().namespace

    @property
    def was_current_actor_reconstructed(self) -> bool:
        c = self._task_ctx
        if not (c and c.actor_id):
            return False
        info = self._runtime().gcs.get_actor_info(c.actor_id)
        return bool(info and info.num_restarts > 0)


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()
