"""Per-process asyncio event-loop core for the control plane.

Reference capability: the C++ runtime's single-threaded asio cores
(``common/asio/instrumented_io_context.h``, ``daemon_core.cc``) — one
event loop per process owns every peer socket, handlers run inline on
the loop, and anything blocking is handed to an executor. This module
is the Python analogue: ONE lazily-started loop thread per process
(``get_loop``), shared by the rpc wire (``aio.py``), the daemon's reply
pump, and the node dispatch pass when ``cfg().async_core`` is on.

Instrumentation (docs/observability.md):

- ``ray_tpu_event_loop_lag_seconds{proc}`` — a scheduled-vs-ran probe:
  a repeating ``call_later`` callback measures how late the loop ran it.
  Sustained lag means a callback is blocking the loop or the loop is
  CPU-saturated; this is the asio ``event_stats`` queue-lag analogue.
- ``ray_tpu_event_loop_slow_callbacks_total{proc}`` — the slow-callback
  watchdog. With ``cfg().async_debug`` on, the loop runs in asyncio
  debug mode with ``slow_callback_duration`` set to
  ``cfg().loop_slow_callback_s``; asyncio's own per-callback timing
  emits a warning through the ``asyncio`` logger for each offender and
  a logging filter counts them here. The always-on lag probe ALSO
  increments the counter when a probe arrives later than the threshold
  (a stalled loop is a slow callback even when debug mode is off).

Thread-affinity contract: callbacks scheduled on the loop are
``#: loop-only`` — thread-context code reaches them via
``loop.call_soon_threadsafe`` (raylint's loop-affinity pass checks
this). ``assert_loop()`` is the runtime sanitizer leg: under
``cfg().lock_sanitizer`` it raises when loop-only code runs off-loop.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
from typing import Any, Callable, Optional

logger = logging.getLogger(__name__)

_LOCK = threading.Lock()
_LOOP: Optional[asyncio.AbstractEventLoop] = None
_LOOP_IDENT: Optional[int] = None   # loop thread's threading.get_ident()
_PROC = ""                          # {proc} label on loop metrics


def set_proc_label(proc: str) -> None:
    """Name this process's loop in metrics ("driver", "head",
    "daemon:<hex8>"). Cheap and idempotent; callable before or after
    the loop starts — the probe reads it per sample."""
    global _PROC
    _PROC = proc


def proc_label() -> str:
    return _PROC or f"pid:{os.getpid()}"


def running() -> bool:
    return _LOOP is not None and not _LOOP.is_closed()


def on_loop() -> bool:
    """True when the calling thread IS the loop thread."""
    return _LOOP_IDENT is not None and \
        threading.get_ident() == _LOOP_IDENT


def assert_loop(what: str = "loop-only code") -> None:
    """Loop-affinity sanitizer: raise when loop-only code executes on a
    non-loop thread. Armed by ``cfg().lock_sanitizer`` (the same knob
    that arms the lock-order sanitizer — both are debug-build checks);
    disarmed it costs one global read."""
    from ray_tpu._private.config import cfg
    if not cfg().lock_sanitizer:
        return
    if _LOOP_IDENT is not None and threading.get_ident() != _LOOP_IDENT:
        raise RuntimeError(
            f"{what} ran on thread "
            f"{threading.current_thread().name!r}, not the event loop "
            f"— hand it to the loop via call_soon_threadsafe")


class _SlowCallbackCounter(logging.Filter):
    """Counts asyncio debug-mode slow-callback warnings ("Executing
    <Handle ...> took 0.123 seconds") into the watchdog counter; the
    warning record itself still propagates to the log."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            msg = record.getMessage()
            if "Executing" in msg and " took " in msg:
                _slow_callback_counter().inc(
                    1.0, tags={"proc": proc_label()})
        except Exception:
            pass    # observability must never break logging
        return True


def _lag_gauge():
    from ray_tpu.util.metrics import Gauge
    return Gauge("ray_tpu_event_loop_lag_seconds",
                 "scheduled-vs-ran lag of the control-plane event loop "
                 "(a repeating call_later probe; sustained lag = a "
                 "blocking callback or a saturated loop)",
                 ("proc",))


def _slow_callback_counter():
    from ray_tpu.util.metrics import Counter
    return Counter("ray_tpu_event_loop_slow_callbacks_total",
                   "event-loop callbacks that overran the "
                   "loop_slow_callback_s threshold (asyncio debug-mode "
                   "timing plus the lag-probe watchdog)",
                   ("proc",))


def _arm_probe(loop: asyncio.AbstractEventLoop) -> None:  #: loop-only
    from ray_tpu._private.config import cfg
    interval = float(cfg().loop_lag_probe_s)
    if interval <= 0:
        return
    threshold = float(cfg().loop_slow_callback_s)
    gauge = _lag_gauge()
    counter = _slow_callback_counter()
    expected = [loop.time() + interval]

    def probe() -> None:
        lag = max(0.0, loop.time() - expected[0])
        gauge.set(lag, tags={"proc": proc_label()})
        if threshold > 0 and lag > threshold:
            # the probe itself arrived late => some callback (or GIL
            # hold) blocked the loop past the threshold — count it even
            # outside debug mode, where asyncio's own timer is off
            counter.inc(1.0, tags={"proc": proc_label()})
        expected[0] = loop.time() + interval
        loop.call_later(interval, probe)

    loop.call_later(interval, probe)


def get_loop() -> asyncio.AbstractEventLoop:
    """The process-wide control-plane loop, started on first use.

    One loop per process by design (the ``daemon_core.cc`` model): the
    wire, the reply pump, and the dispatch pass share it, so their
    cross-thread hand-offs become plain same-thread calls."""
    global _LOOP
    with _LOCK:
        if _LOOP is not None and not _LOOP.is_closed():
            return _LOOP
        loop = asyncio.new_event_loop()
        from ray_tpu._private.config import cfg
        if cfg().async_debug:
            loop.set_debug(True)
            loop.slow_callback_duration = \
                max(1e-4, float(cfg().loop_slow_callback_s))
            aio_logger = logging.getLogger("asyncio")
            if not any(isinstance(f, _SlowCallbackCounter)
                       for f in aio_logger.filters):
                aio_logger.addFilter(_SlowCallbackCounter())

        def run() -> None:
            global _LOOP_IDENT
            _LOOP_IDENT = threading.get_ident()
            asyncio.set_event_loop(loop)
            try:
                loop.run_forever()
            finally:
                _LOOP_IDENT_reset()

        threading.Thread(target=run, daemon=True,
                         name="ray-tpu-loop").start()
        loop.call_soon_threadsafe(_arm_probe, loop)
        _LOOP = loop
        return _LOOP


def _LOOP_IDENT_reset() -> None:
    global _LOOP_IDENT
    _LOOP_IDENT = None


def call_threadsafe(fn: Callable[..., Any], *args: Any) -> None:
    """Schedule ``fn(*args)`` on the loop from any thread."""
    get_loop().call_soon_threadsafe(fn, *args)


def run_coro(coro, timeout: Optional[float] = None) -> Any:
    """Run a coroutine on the loop and block for its result (thread
    context only — calling this ON the loop would deadlock)."""
    if on_loop():
        raise RuntimeError("run_coro called on the event loop thread")
    return asyncio.run_coroutine_threadsafe(coro, get_loop()) \
        .result(timeout)


def shutdown_for_tests() -> None:
    """Stop and drop the singleton loop (test isolation only; the
    production loop is a daemon thread that dies with the process)."""
    global _LOOP
    with _LOCK:
        loop = _LOOP
        _LOOP = None
    if loop is None or loop.is_closed():
        return
    try:
        loop.call_soon_threadsafe(loop.stop)
    except RuntimeError:
        pass


if hasattr(os, "register_at_fork"):
    # a forked child inherits the loop's data structures but not its
    # thread: drop the singleton so the child lazily starts a fresh
    # loop instead of scheduling onto a loop nobody runs
    os.register_at_fork(after_in_child=lambda: (
        globals().__setitem__("_LOOP", None),
        globals().__setitem__("_LOOP_IDENT", None)))
