"""Node memory-pressure controller: fused degradation levels.

Reference Ray treats host-memory pressure as a first-class failure
domain (``common/memory_monitor.h:52`` drives worker-killing policies,
``raylet/local_object_manager.h:101`` spills the plasma store). ray_tpu
splits the same duty across three signals that previously never talked
to each other — host RSS (:class:`MemoryMonitor`), arena occupancy
(``ObjectTable``), and the spill-dir budget. The
:class:`PressureController` fuses them into ONE per-node level:

- ``ok``   — nothing to do;
- ``soft`` — degrade proactively: spill cold arena entries down to the
  soft watermark, throttle push-prefetch admission (worker.py);
- ``hard`` — shed load: reject NEW client reservations/puts with the
  typed retriable :class:`MemoryPressureError` (drivers ride
  ``RetryPolicy`` until relief), let the memory monitor preempt
  over-quota tenants first (``TenantAwarePolicy``), and advertise the
  level through the syncer so ``pick_node`` soft-excludes the node.

Levels only ever degrade service, never correctness: reads (and the
chunk pulls that repair placement) always pass, and a killed worker
surfaces as a typed retriable ``OutOfMemoryError`` — never silent
death. The whole subsystem is gated on ``cfg().memory_pressure``
(default off) and costs nothing when disarmed
(docs/fault_tolerance.md "Memory pressure & graceful degradation").
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Tuple

from ray_tpu._private import failpoints as _fp

LEVEL_OK = "ok"
LEVEL_SOFT = "soft"
LEVEL_HARD = "hard"
LEVELS = (LEVEL_OK, LEVEL_SOFT, LEVEL_HARD)

#: host-RSS headroom below the kill threshold where we call it "soft":
#: start degrading BEFORE the monitor starts shooting workers.
HOST_SOFT_MARGIN = 0.10


def parse_watermarks(raw: str) -> Tuple[float, float]:
    """``"0.70,0.85"`` -> ``(0.70, 0.85)``; malformed input falls back
    to the defaults rather than disabling pressure response."""
    try:
        parts = [float(p) for p in str(raw).split(",")]
        soft, hard = parts[0], parts[1]
        if 0.0 < soft <= hard <= 1.0:
            return soft, hard
    except (ValueError, IndexError):
        pass
    return 0.70, 0.85


def compute_level(host_frac: float, arena_frac: float, spill_frac: float,
                  wm_soft: float, wm_hard: float,
                  host_threshold: float) -> str:
    """Pure fusion rule (unit-tested in tests/test_pressure.py):

    - hard: host RSS at/over the monitor's kill threshold, the arena at
      its hard watermark, or the arena soft-full while the spill-dir
      budget is exhausted (nowhere left to degrade to);
    - soft: host RSS inside :data:`HOST_SOFT_MARGIN` of the threshold,
      or the arena over its soft watermark;
    - ok otherwise.
    """
    if host_frac >= host_threshold or arena_frac >= wm_hard \
            or (arena_frac >= wm_soft and spill_frac >= 1.0):
        return LEVEL_HARD
    if host_frac >= host_threshold - HOST_SOFT_MARGIN \
            or arena_frac >= wm_soft:
        return LEVEL_SOFT
    return LEVEL_OK


def publish_pressure_level(level: str) -> None:
    """``ray_tpu_node_memory_pressure{level}`` enum gauge: 1 on the
    active level's series, 0 on the others (the federation-friendly
    prometheus enum idiom — docs/observability.md)."""
    try:
        from ray_tpu.util.metrics import Gauge
        g = Gauge("ray_tpu_node_memory_pressure",
                  "node memory-pressure level (1 on the active series)",
                  tag_keys=("level",))
        for name in LEVELS:
            g.set(1.0 if name == level else 0.0, tags={"level": name})
    except Exception:
        pass    # metrics must never fail the control path


def count_oom_preemption(reason: str) -> None:
    """The memory monitor preempted one worker — ``reason`` is
    ``tenant_quota`` when the tenant-aware policy picked an over-quota
    job's worker, ``host`` for a plain threshold breach."""
    try:
        from ray_tpu.util.metrics import Counter
        Counter("ray_tpu_oom_preemptions_total",
                "workers preempted by the memory monitor under host "
                "memory pressure",
                tag_keys=("reason",)).inc(1, tags={"reason": reason})
    except Exception:
        pass    # metrics must never fail the control path


class PressureController:
    """Periodically fuses the node's memory signals into a level and
    acts on transitions. Owned by the daemon service (one per node);
    built only when ``cfg().memory_pressure`` is on."""

    def __init__(self, objects, monitor=None,
                 tick_s: Optional[float] = None,
                 watermarks: Optional[str] = None,
                 host_threshold: Optional[float] = None,
                 on_level: Optional[Callable[[str, str], None]] = None):
        from ray_tpu._private.config import cfg
        self.objects = objects
        self.monitor = monitor
        self.tick_s = float(tick_s if tick_s is not None
                            else cfg().pressure_tick_s)
        self.wm_soft, self.wm_hard = parse_watermarks(
            watermarks if watermarks is not None
            else cfg().arena_spill_watermarks)
        self.host_threshold = float(
            host_threshold if host_threshold is not None
            else cfg().memory_usage_threshold)
        self.on_level = on_level
        self.level = LEVEL_OK
        self.ticks = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pressure-controller")

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        publish_pressure_level(self.level)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    # -- sampling ---------------------------------------------------------
    def fractions(self) -> Tuple[float, float, float]:
        """(host, arena, spill) occupancy fractions, each 0.0 when its
        signal is absent (no monitor / no arena / unbounded budget)."""
        host = 0.0
        if self.monitor is not None:
            try:
                limit = max(int(self.monitor.limit), 1)
                host = self.monitor.usage_bytes() / limit
            except Exception:
                host = 0.0
        arena = 0.0
        shm = getattr(self.objects, "_shm", None)
        if shm is not None:
            try:
                arena = shm.used_bytes() / max(self.objects.capacity, 1)
            except Exception:
                arena = 0.0
        spill = 0.0
        budget = int(getattr(self.objects, "spill_budget", 0) or 0)
        if budget:
            spill = self.objects.spilled_bytes() / budget
        return host, arena, spill

    # -- control loop -----------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            try:
                self.tick()
            except Exception:
                pass

    def tick(self) -> str:
        """One fuse-and-act pass; returns the (possibly new) level.
        Failpoint ``pressure.level``: drop = skip this tick, return(X) =
        override the computed level with X — chaos scripts force
        hard-then-relief without real ballast."""
        self.ticks += 1
        level = None
        if _fp.ENABLED:
            fired = _fp.fire("pressure.level", current=self.level)
            if fired is _fp.DROP:
                return self.level
            if isinstance(fired, _fp.Return):
                fired = fired.value
            if isinstance(fired, str) and fired in LEVELS:
                level = fired
        if level is None:
            host, arena, spill = self.fractions()
            level = compute_level(host, arena, spill,
                                  self.wm_soft, self.wm_hard,
                                  self.host_threshold)
        if level != self.level:
            old, self.level = self.level, level
            publish_pressure_level(level)
            if self.on_level is not None:
                try:
                    self.on_level(old, level)
                except Exception:
                    pass
        if level != LEVEL_OK:
            # proactive degradation: walk the arena back under its soft
            # watermark off cold, unpinned entries (pins always win)
            try:
                self.objects.spill_to_fraction(self.wm_soft)
            except Exception:
                pass
        return self.level
