"""Core-op microbenchmarks (reference: `python/ray/_private/ray_perf.py:95`
— the harness behind `ray microbenchmark`)."""

from __future__ import annotations

import time
from typing import Callable, Dict, List

import numpy as np


def _timeit(name: str, fn: Callable[[], int],
            duration_s: float = 2.0) -> Dict:
    # warmup
    fn()
    t0 = time.perf_counter()
    count = 0
    while time.perf_counter() - t0 < duration_s:
        count += fn()
    dt = time.perf_counter() - t0
    return {"name": name, "throughput_per_s": round(count / dt, 1),
            "count": count, "seconds": round(dt, 3)}


def run_microbenchmarks(duration_s: float = 2.0) -> List[Dict]:
    """Boot a runtime and measure core-op throughputs."""
    import ray_tpu

    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8})
    results: List[Dict] = []

    @ray_tpu.remote
    def noop():
        return None

    def tasks_batch():
        ray_tpu.get([noop.remote() for _ in range(100)])
        return 100
    results.append(_timeit("tasks_per_second", tasks_batch, duration_s))

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()

    def actor_batch():
        ray_tpu.get([a.m.remote() for _ in range(100)])
        return 100
    results.append(_timeit("actor_calls_per_second", actor_batch,
                           duration_s))

    small = np.zeros(8, np.float64)

    def put_small():
        refs = [ray_tpu.put(small) for _ in range(100)]
        del refs
        return 100
    results.append(_timeit("puts_small_per_second", put_small, duration_s))

    big = np.zeros(1024 * 1024, np.uint8)  # 1 MiB

    def put_get_1mb():
        for _ in range(10):
            ray_tpu.get(ray_tpu.put(big))
        return 10
    results.append(_timeit("put_get_1MiB_per_second", put_get_1mb,
                           duration_s))

    # compiled-DAG shm-channel rounds (zero-RPC steady state) through a
    # 2-stage process-worker pipeline; in daemons mode the actors are
    # daemon-remote so the DAG legitimately falls back to the dynamic
    # schedule — the row then measures THAT path (labeled by mode).
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class _Stage:
        def f(self, x):
            return x + 1

    s1, s2 = _Stage.remote(), _Stage.remote()
    ray_tpu.get([s1.f.remote(0), s2.f.remote(0)])
    with InputNode() as inp:
        dag = s2.f.bind(s1.f.bind(inp))
    compiled = dag.experimental_compile()

    def dag_rounds():
        refs = [compiled.execute(i) for i in range(50)]
        for r in refs:
            ray_tpu.get(r)
        return 50
    results.append(_timeit("compiled_dag_execs_per_second", dag_rounds,
                           duration_s))
    compiled.teardown()

    if own:
        ray_tpu.shutdown()
    return results


def queued_task_drain(n: int = 10_000) -> Dict:
    """Scale envelope probe (reference: release/benchmarks/README.md:25-31
    — 1M+ tasks queued on one node): submit ``n`` no-op tasks without
    consuming, then drain them all."""
    import ray_tpu

    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8})

    @ray_tpu.remote
    def noop():
        return None

    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    t_submit = time.perf_counter() - t0
    ray_tpu.get(refs)
    t_total = time.perf_counter() - t0
    if own:
        ray_tpu.shutdown()
    return {"name": f"queued_{n}_task_drain",
            "n": n,
            "submit_seconds": round(t_submit, 3),
            "total_seconds": round(t_total, 3),
            "submit_per_s": round(n / t_submit, 1),
            "drain_per_s": round(n / t_total, 1)}


def main() -> int:
    """Emit one JSON line per benchmark for the current mode (set
    RAY_TPU_CLUSTER=daemons for cluster mode); used by tools/gen_perf.py
    to produce the committed PERF.md."""
    import json
    import os
    import sys

    duration = float(os.environ.get("PERF_DURATION_S", "2.0"))
    drain_n = int(os.environ.get("PERF_DRAIN_N", "10000"))
    for row in run_microbenchmarks(duration_s=duration):
        print(json.dumps(row))
        sys.stdout.flush()
    print(json.dumps(queued_task_drain(drain_n)))
    sys.stdout.flush()
    # scaling TREND: does the drain rate hold at 3x the backlog?
    print(json.dumps(queued_task_drain(3 * drain_n)))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
