"""Core-op microbenchmarks (reference: `python/ray/_private/ray_perf.py:95`
— the harness behind `ray microbenchmark`)."""

from __future__ import annotations

import time
from typing import Callable, Dict, List

import numpy as np


def _timeit(name: str, fn: Callable[[], int],
            duration_s: float = 2.0) -> Dict:
    # warmup
    fn()
    t0 = time.perf_counter()
    count = 0
    while time.perf_counter() - t0 < duration_s:
        count += fn()
    dt = time.perf_counter() - t0
    return {"name": name, "throughput_per_s": round(count / dt, 1),
            "count": count, "seconds": round(dt, 3)}


def run_microbenchmarks(duration_s: float = 2.0) -> List[Dict]:
    """Boot a runtime and measure core-op throughputs."""
    import ray_tpu

    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8})
    results: List[Dict] = []

    @ray_tpu.remote
    def noop():
        return None

    def tasks_batch():
        ray_tpu.get([noop.remote() for _ in range(100)])
        return 100
    results.append(_timeit("tasks_per_second", tasks_batch, duration_s))

    @ray_tpu.remote
    class A:
        def m(self):
            return None

    a = A.remote()

    def actor_batch():
        ray_tpu.get([a.m.remote() for _ in range(100)])
        return 100
    results.append(_timeit("actor_calls_per_second", actor_batch,
                           duration_s))

    small = np.zeros(8, np.float64)

    def put_small():
        refs = [ray_tpu.put(small) for _ in range(100)]
        del refs
        return 100
    results.append(_timeit("puts_small_per_second", put_small, duration_s))

    big = np.zeros(1024 * 1024, np.uint8)  # 1 MiB

    def put_get_1mb():
        for _ in range(10):
            ray_tpu.get(ray_tpu.put(big))
        return 10
    results.append(_timeit("put_get_1MiB_per_second", put_get_1mb,
                           duration_s))

    # compiled-DAG shm-channel rounds (zero-RPC steady state) through a
    # 2-stage process-worker pipeline; in daemons mode the actors are
    # daemon-remote so the DAG legitimately falls back to the dynamic
    # schedule — the row then measures THAT path (labeled by mode).
    from ray_tpu.dag import InputNode

    @ray_tpu.remote
    class _Stage:
        def f(self, x):
            return x + 1

    s1, s2 = _Stage.remote(), _Stage.remote()
    ray_tpu.get([s1.f.remote(0), s2.f.remote(0)])
    with InputNode() as inp:
        dag = s2.f.bind(s1.f.bind(inp))
    compiled = dag.experimental_compile()

    def dag_rounds():
        refs = [compiled.execute(i) for i in range(50)]
        for r in refs:
            ray_tpu.get(r)
        return 50
    results.append(_timeit("compiled_dag_execs_per_second", dag_rounds,
                           duration_s))
    compiled.teardown()

    if own:
        ray_tpu.shutdown()
    return results


def queued_task_drain(n: int = 10_000) -> Dict:
    """Scale envelope probe (reference: release/benchmarks/README.md:25-31
    — 1M+ tasks queued on one node): submit ``n`` no-op tasks without
    consuming, then drain them all."""
    import ray_tpu

    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8})

    @ray_tpu.remote
    def noop():
        return None

    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    t_submit = time.perf_counter() - t0
    ray_tpu.get(refs)
    t_total = time.perf_counter() - t0
    if own:
        ray_tpu.shutdown()
    return {"name": f"queued_{n}_task_drain",
            "n": n,
            "submit_seconds": round(t_submit, 3),
            "total_seconds": round(t_total, 3),
            "submit_per_s": round(n / t_submit, 1),
            "drain_per_s": round(n / t_total, 1)}


def burst_submit_batched(n: int = 3000) -> Dict:
    """Burst-submit tasks on the CLASSIC wire path (two returns keeps
    them off the native fast lane), so the daemons topology measures the
    submit coalescer end to end: push_task_batch frames out, batched
    task_batch_done completions back."""
    import ray_tpu

    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8})

    @ray_tpu.remote(num_returns=2)
    def duo():
        return None, None

    t0 = time.perf_counter()
    refs = [duo.remote() for _ in range(n)]
    t_submit = time.perf_counter() - t0
    ray_tpu.get([r for ab in refs for r in ab])
    t_total = time.perf_counter() - t0
    if own:
        ray_tpu.shutdown()
    return {"name": "burst_submit_batched", "n": n,
            "submit_seconds": round(t_submit, 3),
            "total_seconds": round(t_total, 3),
            "submit_per_s": round(n / t_submit, 1),
            "drain_per_s": round(n / t_total, 1)}


def main() -> int:
    """Emit one JSON line per benchmark for the current mode (set
    RAY_TPU_CLUSTER=daemons for cluster mode); used by tools/gen_perf.py
    to produce the committed PERF.md."""
    import json
    import os
    import sys

    duration = float(os.environ.get("PERF_DURATION_S", "2.0"))
    drain_n = int(os.environ.get("PERF_DRAIN_N", "10000"))
    for row in run_microbenchmarks(duration_s=duration):
        print(json.dumps(row))
        sys.stdout.flush()
    print(json.dumps(burst_submit_batched()))
    sys.stdout.flush()
    print(json.dumps(queued_task_drain(drain_n)))
    sys.stdout.flush()
    # scaling TREND: does the drain rate hold at 3x the backlog?
    print(json.dumps(queued_task_drain(3 * drain_n)))
    sys.stdout.flush()
    if os.environ.get("PERF_ENVELOPE") == "1":
        for row in envelope_rows():
            print(json.dumps(row))
            sys.stdout.flush()
    return 0


def envelope_rows() -> List[Dict]:
    """Scale-envelope slices (reference: release/benchmarks/README.md
    1M+ queued / 40k actors / 2,000 nodes): 100k-task drain, 5k live
    actors, 64-virtual-node spread — committed as PERF.md evidence."""
    import ray_tpu

    rows: List[Dict] = []
    own = not ray_tpu.is_initialized()
    if own:
        ray_tpu.init(num_nodes=1, resources={"CPU": 8})

    @ray_tpu.remote(_in_process=True)
    def val(i):
        return i

    # scaled queued-drain: climb the backlog ladder until the box
    # cannot hold the next rung (memory/thread/PID limits) or a rung
    # blows the time budget. Every rung that held is committed, so
    # PERF.md records the LARGEST backlog this box drains plus the
    # rate trend on the way up — degrading gracefully on small hosts
    # instead of losing the whole section to one oversized slice.
    import os as _os
    budget_s = float(_os.environ.get("PERF_ENVELOPE_DRAIN_BUDGET_S",
                                     "120"))
    for n in (100_000, 300_000, 1_000_000):
        t0 = time.perf_counter()
        try:
            refs = [val.remote(i) for i in range(n)]
            submit_s = time.perf_counter() - t0
            out = ray_tpu.get(refs)
            total_s = time.perf_counter() - t0
            assert out[-1] == n - 1
            del refs, out
        except Exception:
            break       # previous rung stands as the box's envelope
        rows.append({"name": f"queued_{n}_task_drain", "n": n,
                     "submit_seconds": round(submit_s, 3),
                     "total_seconds": round(total_s, 3),
                     "submit_per_s": round(n / submit_s, 1),
                     "drain_per_s": round(n / total_s, 1)})
        if total_s > budget_s:
            break       # next rung would run 3x past the budget

    @ray_tpu.remote(_in_process=True)
    class Cell:
        def __init__(self, i):
            self.i = i

        def get(self):
            return self.i

    t0 = time.perf_counter()
    cells = [Cell.remote(i) for i in range(5000)]
    out = ray_tpu.get([c.get.remote() for c in cells])
    total_s = time.perf_counter() - t0
    assert out[-1] == 4999
    rows.append({"name": "actors_5000_create_and_call",
                 "throughput_per_s": round(5000 / total_s, 1),
                 "count": 5000, "seconds": round(total_s, 3)})
    for c in cells:
        ray_tpu.kill(c)

    # 64-node spread: its own runtime (node count is an init
    # parameter) — the current one must go either way
    ray_tpu.shutdown()
    rt = ray_tpu.init(num_nodes=64, resources={"CPU": 2})

    @ray_tpu.remote(_in_process=True, scheduling_strategy="SPREAD")
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    t0 = time.perf_counter()
    nodes = set(ray_tpu.get([where.remote() for _ in range(256)]))
    total_s = time.perf_counter() - t0
    rows.append({"name": "spread_256_tasks_64_nodes",
                 "throughput_per_s": round(256 / total_s, 1),
                 "count": len(nodes), "seconds": round(total_s, 3)})
    ray_tpu.shutdown()
    return rows


if __name__ == "__main__":
    raise SystemExit(main())
