"""Task event + span buffer, chrome-trace timeline export, and the
per-phase latency instrumentation helpers.

Reference: `src/ray/core_worker/task_event_buffer.cc` (per-worker event
buffering) → `gcs/gcs_task_manager.h:94` (cluster task events) →
`ray timeline` chrome-trace dump (`_private/state.py:438`).

Beyond plain lifecycle events (RUNNING/FINISHED/...), the buffer holds
``SPAN`` events: per-phase latency slices recorded at every lifecycle
seam on every process (driver submit/linger/queue/result, daemon
dispatch, worker exec). Each process buffers its own spans; daemons and
their workers flush to the head's task-event store by piggybacking on
heartbeats (``daemon.py`` main loop, ``trace.flush`` failpoint), the
driver flushes through ``ClusterBackend.start_task_event_flusher``. The
head applies a per-node clock offset on ingestion so a merged timeline
(:func:`merged_chrome_trace`) lines up lanes from different hosts.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

# The per-task phases surfaced by ``task_breakdown`` and the
# ``ray_tpu_task_phase_seconds`` histogram:
#   submit        driver: ``submit_task`` entry -> node backlog enqueue
#   linger        driver: submit-coalescer enqueue -> batch flush on the wire
#   queue         driver: node backlog enqueue -> dispatch-loop admission
#   dispatch      daemon: task frame arrival -> exec request sent to a worker
#   exec          worker: user function body (start -> finish)
#   result_flush  daemon: completion buffered on the reply pump -> its
#                 task_batch_done frame on the wire (drain-side linger)
#   result_ingest driver: batch frame arrival -> waiter threads woken
#   result        driver: outcome decoded -> return futures completed
PHASES = ("submit", "linger", "queue", "dispatch", "exec",
          "result_flush", "result_ingest", "result")

# Process-stable wall<->monotonic anchor: spans convert the monotonic
# timestamps their callers ALREADY hold into wall time arithmetically,
# instead of issuing extra clock reads per event — on sandboxed/traced
# kernels a clock syscall under thread contention costs 100x its normal
# price, and the span hot paths run on submit/dispatch/reader threads.
_MONO0 = time.perf_counter()
_WALL0 = time.time()


def wall_at(mono: float) -> float:
    """Wall-clock time of a ``time.perf_counter()`` reading (anchored at
    import; drift over a process lifetime is negligible for tracing)."""
    return _WALL0 + (mono - _MONO0)


class TaskEventBuffer:
    """Ring buffer of task lifecycle + span events.

    Two lanes share one sequence counter:

    - **lifecycle lane** (``record``): dict events under a lock — the
      pre-existing RUNNING/FINISHED/... path, low rate per task.
    - **span lane** (``record_span``): LOCK-FREE tuple appends.
      Per-phase spans fire several times per task from the submit,
      dispatch, worker-pump, and reader threads at once; a shared lock
      there turns into futex convoys (catastrophic on syscall-traced
      sandbox kernels). ``deque.append`` is GIL-atomic and
      ``itertools.count`` hands out seqs without a lock; tuples
      materialize into event dicts only at read time (flushes/queries,
      ~1/s). Readers retry the rare iteration-vs-append race.
    """

    def __init__(self, capacity: int = 100_000):
        import itertools
        from ray_tpu._private.lock_sanitizer import tracked_lock
        self._events: deque = deque(maxlen=capacity)  #: guarded by self._lock
        # _spans is DELIBERATELY lock-free (GIL-atomic appends): a lock
        # on the multi-thread span hot path cost ~600us/span in futex
        # convoys (PR 4) — do not annotate it as guarded
        self._spans: deque = deque(maxlen=capacity)
        self._lock = tracked_lock("events.buffer", reentrant=False)
        self._t0 = time.perf_counter()
        self._seq_counter = itertools.count(1)

    def record(self, *, task_id: str, name: str, event: str,
               node_id: str = "", actor_id: str = "",
               extra: Optional[Dict] = None,
               mono: Optional[float] = None) -> None:
        """``mono`` is an optional pre-read ``perf_counter()`` timestamp:
        callers that already hold one save the event its clock reads."""
        if mono is None:
            mono = time.perf_counter()
        with self._lock:
            # seq INSIDE the lock: taken outside, a preempted recorder
            # could append after a flush advanced the cursor past its
            # seq — the event would be skipped forever. (The span lane
            # accepts that nanosecond window as its lock-free tradeoff;
            # lifecycle transitions must not.)
            self._events.append({
                "seq": next(self._seq_counter),
                "task_id": task_id, "name": name, "event": event,
                "node_id": node_id, "actor_id": actor_id,
                "ts_us": (mono - self._t0) * 1e6,
                "wall_ts": wall_at(mono),
                **(extra or {})})

    def record_span(self, *, task_id: str, name: str, phase: str,
                    dur_s: float, node_id: str = "", proc: str = "",
                    trace_id: str = "",
                    start_wall: Optional[float] = None,
                    end_mono: Optional[float] = None,
                    end_wall: Optional[float] = None) -> None:
        """One per-phase latency slice (event type ``SPAN``); lock-free.
        ``end_wall`` is for spans ingested from ANOTHER process (their
        wall clock is authoritative); local recorders pass/let default
        ``end_mono`` and the wall time derives at materialization."""
        if end_mono is None and end_wall is None:
            end_mono = time.perf_counter()
        self._spans.append((
            next(self._seq_counter), task_id, name, phase,
            float(dur_s), node_id, proc, trace_id, start_wall,
            end_mono, end_wall))

    def _materialize(self, t) -> Dict[str, Any]:
        (seq, task_id, name, phase, dur_s, node_id, proc, trace_id,
         start_wall, end_mono, end_wall) = t
        if end_wall is None:
            end_wall = wall_at(end_mono)
        if start_wall is None:
            start_wall = end_wall - dur_s
        return {"seq": seq, "task_id": task_id, "name": name,
                "event": "SPAN", "node_id": node_id,
                "wall_ts": end_wall, "phase": phase, "dur_s": dur_s,
                "proc": proc, "trace_id": trace_id,
                "start_wall": start_wall}

    def _span_snapshot(self) -> list:
        # lock-free writers can mutate mid-iteration; list() is C-speed,
        # so a few retries always win. Give up empty (next read catches
        # up — the flush cursor only advances on what it actually saw).
        for _ in range(16):
            try:
                return list(self._spans)
            except RuntimeError:
                continue
        return []

    def extend(self, events: Iterable[Dict[str, Any]]) -> None:
        """Append foreign events (another process's flush) preserving
        their order; sequence numbers are re-assigned locally so
        ``events_after`` cursors stay monotonic."""
        with self._lock:
            for ev in events:
                e = dict(ev)
                e["seq"] = next(self._seq_counter)
                self._events.append(e)

    @classmethod
    def from_events(cls, events: List[Dict[str, Any]],
                    capacity: int = 100_000) -> "TaskEventBuffer":
        buf = cls(capacity=max(capacity, len(events) or 1))
        buf.extend(events)
        return buf

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._events)
        out.extend(self._materialize(t) for t in self._span_snapshot())
        out.sort(key=lambda e: e["seq"])
        return out

    def events_after(self, cursor: int) -> List[Dict[str, Any]]:
        """Events with seq > cursor (the head-store flusher's incremental
        read; reference: task_event_buffer.cc periodic flush). Seqs are
        assigned near-contiguously, so walk back from the TAIL and stop
        shortly past the cursor — O(new events), not a full O(n) deque
        scan per flush. (The small slack absorbs the lock-free span
        lane's momentary append disorder.)"""
        out: List[Dict[str, Any]] = []
        slack = cursor - 64
        with self._lock:
            for ev in reversed(self._events):
                if ev["seq"] <= slack:
                    break
                if ev["seq"] > cursor:
                    out.append(ev)
        spans = self._span_snapshot()
        stale_run = 0
        for t in reversed(spans):
            if t[0] <= slack:
                # don't break on the FIRST stale item: one late
                # lock-free append can park a low seq at the tail, and
                # breaking there would hide every unsent span behind it
                # forever. A RUN of stale items is the real boundary.
                stale_run += 1
                if stale_run > 8:
                    break
                continue
            stale_run = 0
            if t[0] > cursor:
                out.append(self._materialize(t))
        out.sort(key=lambda e: e["seq"])
        return out

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._spans.clear()

    # -- chrome trace ----------------------------------------------------
    def chrome_trace(self) -> List[Dict[str, Any]]:
        """Pair RUNNING/FINISHED events into chrome 'X' duration slices."""
        started: Dict[str, Dict] = {}
        slices: List[Dict[str, Any]] = []
        for ev in self.events():
            kind = ev["event"]
            if kind == "RUNNING":
                # A second RUNNING for the same task is a RETRY's fresh
                # attempt: the stale start (whose attempt died without a
                # terminal event) is dropped so the retry's FINISHED
                # pairs with ITS OWN start, not the dead attempt's.
                started[ev["task_id"]] = ev
            elif kind in ("RETRY", "RETRY_OOM"):
                started.pop(ev["task_id"], None)
            elif kind in ("FINISHED", "FAILED"):
                beg = started.pop(ev["task_id"], None)
                if beg is None:
                    continue
                slices.append({
                    "name": ev["name"] or ev["task_id"][:8],
                    "cat": "task",
                    "ph": "X",
                    "ts": beg["ts_us"],
                    "dur": max(ev["ts_us"] - beg["ts_us"], 1.0),
                    "pid": ev["node_id"][:8] or "driver",
                    "tid": ev["task_id"][:8],
                    "args": {"status": kind},
                })
        return slices

    def dump_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


def merged_chrome_trace(events: List[Dict[str, Any]]
                        ) -> List[Dict[str, Any]]:
    """Cluster-wide chrome trace over MERGED events (driver buffer +
    head store): one lane (chrome ``pid``) per recording process
    (driver / daemon:<node> / worker:<pid>), wall-clock timebase with
    the head's per-node clock offset already applied at ingestion."""
    slices: List[Dict[str, Any]] = []
    started: Dict[tuple, Dict] = {}
    for ev in sorted(events, key=lambda e: e.get("wall_ts", 0.0)):
        kind = ev.get("event")
        proc = ev.get("proc") or "driver"
        task = ev.get("task_id", "")
        if kind == "SPAN":
            dur_s = float(ev.get("dur_s", 0.0))
            start = float(ev.get("start_wall",
                                 ev.get("wall_ts", 0.0) - dur_s))
            slices.append({
                "name": f"{ev.get('phase', 'span')}:"
                        f"{ev.get('name') or task[:8]}",
                "cat": "phase", "ph": "X",
                "ts": start * 1e6,
                "dur": max(dur_s * 1e6, 1.0),
                "pid": proc, "tid": task[:8],
                "args": {"phase": ev.get("phase"), "task_id": task,
                         "trace_id": ev.get("trace_id", ""),
                         "node_id": ev.get("node_id", ""),
                         "clock_off": ev.get("clock_off", 0.0)},
            })
        elif kind == "RUNNING":
            started[(proc, task)] = ev
        elif kind in ("RETRY", "RETRY_OOM"):
            started.pop((proc, task), None)
        elif kind in ("FINISHED", "FAILED"):
            beg = started.pop((proc, task), None)
            if beg is None:
                continue
            slices.append({
                "name": ev.get("name") or task[:8],
                "cat": "task", "ph": "X",
                "ts": beg.get("wall_ts", 0.0) * 1e6,
                "dur": max((ev.get("wall_ts", 0.0)
                            - beg.get("wall_ts", 0.0)) * 1e6, 1.0),
                "pid": proc, "tid": task[:8],
                "args": {"status": kind,
                         "node_id": ev.get("node_id", "")},
            })
    return slices


# ---------------------------------------------------------------------------
# trace context + phase instrumentation
# ---------------------------------------------------------------------------

def stamp_trace(spec) -> None:
    """Stamp the trace context into a TaskSpec at submission time (the
    context rides the spec across the wire to daemons and workers).
    Sampling is deterministic in the task id so every process agrees."""
    from ray_tpu._private import config as _config
    c = _config._config        # lock-free fast path (identity-stable
    if c is None:              # until apply_system_config/reset)
        c = _config.cfg()
    if not c.task_trace:
        return
    rate = c.trace_sample
    if rate <= 0.0:
        return
    if rate < 1.0:
        frac = int(spec.task_id.hex()[:8], 16) / 0xFFFFFFFF
        if frac >= rate:
            return
    spec.trace_sampled = True
    if not spec.trace_id:
        spec.trace_id = spec.task_id.hex()[:16]
    spec.submit_mono = time.perf_counter()
    spec.submit_wall = wall_at(spec.submit_mono)


_PHASE_HIST = None


def phase_histogram():
    """The per-phase latency histogram. Cached module-locally (the
    get-or-create registry path costs a lock per call on the span hot
    path); a cleared registry re-materializes it on the next call."""
    global _PHASE_HIST
    from ray_tpu.util import metrics as _metrics
    h = _PHASE_HIST
    if h is not None and _metrics._REGISTRY.get(h.name) is h:
        return h
    h = _metrics.Histogram(
        "ray_tpu_task_phase_seconds",
        "per-phase task latency: submit|linger|queue|dispatch|exec|"
        "result_flush|result_ingest|result",
        boundaries=(0.0005, 0.005, 0.05, 0.5, 5.0),
        tag_keys=("phase", "node_id"))
    _PHASE_HIST = h
    return h


def record_phase(buf: Optional[TaskEventBuffer], *, task_id: str,
                 name: str, phase: str, dur_s: float, node_id: str,
                 proc: str, trace_id: str = "",
                 start_wall: Optional[float] = None,
                 end_mono: Optional[float] = None) -> None:
    """Append one span to ``buf`` (when given) and feed the phase
    histogram. Never raises: observability must not fail the task."""
    try:
        if buf is not None:
            buf.record_span(task_id=task_id, name=name, phase=phase,
                            dur_s=dur_s, node_id=node_id, proc=proc,
                            trace_id=trace_id, start_wall=start_wall,
                            end_mono=end_mono)
        phase_histogram().observe(dur_s, tags={"phase": phase,
                                               "node_id": node_id})
    except Exception:
        pass


def record_phase_rt(spec, phase: str, dur_s: float, node_id: str,
                    start_wall: Optional[float] = None,
                    end_mono: Optional[float] = None) -> None:
    """Driver-side convenience: record into the global runtime's buffer
    with lane ``driver``."""
    from ray_tpu._private import worker as _worker
    rt = _worker.global_runtime()
    buf = getattr(rt, "task_events", None) if rt is not None else None
    record_phase(buf, task_id=spec.task_id.hex(), name=spec.name,
                 phase=phase, dur_s=dur_s, node_id=node_id,
                 proc="driver", trace_id=getattr(spec, "trace_id", ""),
                 start_wall=start_wall, end_mono=end_mono)


def ingest_span_events(buf: Optional[TaskEventBuffer],
                       events: List[Dict[str, Any]]) -> None:
    """Merge span events flushed from another process (worker exec
    spans riding result frames) into this process's buffer and
    histogram. SPAN events take the lock-free span lane — this runs on
    the hot reader threads — keeping their ORIGIN wall clock."""
    if not events:
        return
    hist = phase_histogram()
    for ev in events:
        if ev.get("event") == "SPAN" and ev.get("phase"):
            if buf is not None:
                buf.record_span(
                    task_id=ev.get("task_id", ""),
                    name=ev.get("name", ""), phase=ev["phase"],
                    dur_s=float(ev.get("dur_s", 0.0)),
                    node_id=ev.get("node_id", ""),
                    proc=ev.get("proc", ""),
                    trace_id=ev.get("trace_id", ""),
                    start_wall=ev.get("start_wall"),
                    end_wall=ev.get("wall_ts"))
            try:
                hist.observe(float(ev.get("dur_s", 0.0)),
                             tags={"phase": ev["phase"],
                                   "node_id": ev.get("node_id", "")})
            except Exception:
                pass
        elif buf is not None:
            buf.extend([ev])
