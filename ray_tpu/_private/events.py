"""Task event buffer + chrome-trace timeline export.

Reference: `src/ray/core_worker/task_event_buffer.cc` (per-worker event
buffering) → `gcs/gcs_task_manager.h:94` (cluster task events) →
`ray timeline` chrome-trace dump (`_private/state.py:438`).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional


class TaskEventBuffer:
    """Ring buffer of task lifecycle events."""

    def __init__(self, capacity: int = 100_000):
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._seq = 0

    def record(self, *, task_id: str, name: str, event: str,
               node_id: str = "", actor_id: str = "",
               extra: Optional[Dict] = None) -> None:
        with self._lock:
            self._seq += 1
            self._events.append({
                "seq": self._seq,
                "task_id": task_id, "name": name, "event": event,
                "node_id": node_id, "actor_id": actor_id,
                "ts_us": (time.perf_counter() - self._t0) * 1e6,
                "wall_ts": time.time(),
                **(extra or {})})

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def events_after(self, cursor: int) -> List[Dict[str, Any]]:
        """Events with seq > cursor (the head-store flusher's incremental
        read; reference: task_event_buffer.cc periodic flush)."""
        with self._lock:
            return [ev for ev in self._events if ev["seq"] > cursor]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    # -- chrome trace ----------------------------------------------------
    def chrome_trace(self) -> List[Dict[str, Any]]:
        """Pair RUNNING/FINISHED events into chrome 'X' duration slices."""
        started: Dict[str, Dict] = {}
        slices: List[Dict[str, Any]] = []
        for ev in self.events():
            if ev["event"] == "RUNNING":
                started[ev["task_id"]] = ev
            elif ev["event"] in ("FINISHED", "FAILED"):
                beg = started.pop(ev["task_id"], None)
                if beg is None:
                    continue
                slices.append({
                    "name": ev["name"] or ev["task_id"][:8],
                    "cat": "task",
                    "ph": "X",
                    "ts": beg["ts_us"],
                    "dur": max(ev["ts_us"] - beg["ts_us"], 1.0),
                    "pid": ev["node_id"][:8] or "driver",
                    "tid": ev["task_id"][:8],
                    "args": {"status": ev["event"]},
                })
        return slices

    def dump_chrome_trace(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path
