"""Distributed reference counting and lineage tracking.

Parity contract (reference ``src/ray/core_worker/reference_count.h`` and
``task_manager.h``): an object stays alive while any of these hold:
local Python handles, pending tasks that take it as an argument, or nested
containment inside another live object. When the count reaches zero the value
is freed from every store and its lineage entry released. Lineage (the task
that produced each object) is retained while the object or any downstream
dependent is alive, enabling reconstruction after node loss
(``object_recovery_manager.h``).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set

from ray_tpu._private.ids import ObjectID, TaskID


@dataclass
class Reference:
    local_refs: int = 0
    submitted_task_refs: int = 0
    # objects whose serialized payload contains this one (containment pins)
    contained_in: Set[ObjectID] = field(default_factory=set)
    contains: Set[ObjectID] = field(default_factory=set)
    # never collect (e.g. detached-actor state, named objects)
    pinned: bool = False

    def total(self) -> int:
        return (self.local_refs + self.submitted_task_refs
                + len(self.contained_in) + (1 if self.pinned else 0))


class ReferenceCounter:
    def __init__(self, on_zero: Optional[Callable[[ObjectID], None]] = None):
        from ray_tpu._private.lock_sanitizer import tracked_lock
        self._lock = tracked_lock("refcount")
        self._refs: Dict[ObjectID, Reference] = {}  #: guarded by self._lock
        self._on_zero = on_zero
        # Per-thread deferral queue: freeing an object can drop values whose
        # ObjectRef.__del__ re-enters this counter from inside on_zero (and
        # from inside store/lineage locks). Cascaded decrements are queued
        # and drained iteratively by the outermost call — no recursion, no
        # lock re-entry (reference: reference_count.h runs deletions on the
        # owner's io_service for the same reason).
        self._tls = threading.local()

    def set_on_zero(self, cb: Callable[[ObjectID], None]) -> None:
        self._on_zero = cb

    def _get(self, oid: ObjectID) -> Reference:
        # callers hold self._lock; the re-entrant acquire makes this
        # helper independently safe (and visibly lock-correct)
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                ref = self._refs[oid] = Reference()
            return ref

    # -- local handles -----------------------------------------------------
    def add_local_ref(self, oid: ObjectID) -> None:
        with self._lock:
            self._get(oid).local_refs += 1

    def remove_local_ref(self, oid: ObjectID) -> None:
        self._dec(oid, "local_refs")

    # -- task argument pins ------------------------------------------------
    def add_submitted_task_refs(self, oids: List[ObjectID]) -> None:
        with self._lock:
            for oid in oids:
                self._get(oid).submitted_task_refs += 1

    def remove_submitted_task_refs(self, oids: List[ObjectID]) -> None:
        """Drop one submitted-task pin per listed oid — the whole batch
        decrements under ONE lock acquisition (the drain-side path
        releases a completed task's arg pins together; per-oid _dec
        paid a lock round-trip each). Frees cascade outside the lock
        through the same deferral queue as single decrements."""
        pending = getattr(self._tls, "pending", None)
        if pending is not None:     # nested call: defer to outermost
            pending.extend((oid, "submitted_task_refs") for oid in oids)
            return
        self._tls.pending = pending = []
        try:
            zeroed: List[ObjectID] = []
            with self._lock:
                for oid in oids:
                    ref = self._refs.get(oid)
                    if ref is None:
                        continue
                    if ref.submitted_task_refs > 0:
                        ref.submitted_task_refs -= 1
                    if ref.total() == 0 and oid not in zeroed:
                        # a duplicated oid in the batch zeroes once
                        zeroed.append(oid)
            for oid in zeroed:
                self._maybe_free(oid)
            while pending:
                nxt_oid, nxt_attr = pending.pop(0)
                self._dec_now(nxt_oid, nxt_attr)
        finally:
            self._tls.pending = None

    # -- containment (nested refs inside stored values) --------------------
    def add_nested_refs(self, outer: ObjectID, inner: List[ObjectID]) -> None:
        with self._lock:
            for oid in inner:
                self._get(oid).contained_in.add(outer)
                self._get(outer).contains.add(oid)

    # -- pinning -----------------------------------------------------------
    def pin(self, oid: ObjectID) -> None:
        with self._lock:
            self._get(oid).pinned = True

    def unpin(self, oid: ObjectID) -> None:
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None or not ref.pinned:
                return
            ref.pinned = False
        self._maybe_free(oid)

    # -- internals ---------------------------------------------------------
    def _dec(self, oid: ObjectID, attr: str) -> None:
        pending = getattr(self._tls, "pending", None)
        if pending is not None:     # nested call: defer to outermost frame
            pending.append((oid, attr))
            return
        self._tls.pending = pending = []
        try:
            self._dec_now(oid, attr)
            while pending:
                nxt_oid, nxt_attr = pending.pop(0)
                self._dec_now(nxt_oid, nxt_attr)
        finally:
            self._tls.pending = None

    def _dec_now(self, oid: ObjectID, attr: str) -> None:
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None:
                return
            cur = getattr(ref, attr)
            if cur > 0:
                setattr(ref, attr, cur - 1)
        self._maybe_free(oid)

    def _maybe_free(self, oid: ObjectID) -> None:
        to_free: List[ObjectID] = []
        with self._lock:
            ref = self._refs.get(oid)
            if ref is None or ref.total() > 0:
                return
            del self._refs[oid]
            to_free.append(oid)
            # release containment pins held by this object
            stack = list(ref.contains)
            while stack:
                inner_id = stack.pop()
                inner = self._refs.get(inner_id)
                if inner is None:
                    continue
                inner.contained_in.discard(oid)
                if inner.total() == 0:
                    del self._refs[inner_id]
                    to_free.append(inner_id)
                    stack.extend(inner.contains)
        if self._on_zero is not None:
            for freed in to_free:
                try:
                    self._on_zero(freed)
                except Exception:
                    pass

    def ref_count(self, oid: ObjectID) -> int:
        with self._lock:
            ref = self._refs.get(oid)
            return 0 if ref is None else ref.total()

    def num_tracked(self) -> int:
        with self._lock:
            return len(self._refs)


class LineageTable:
    """object → producing-task map used for reconstruction after loss."""

    def __init__(self, max_entries: int = 1_000_000):
        from ray_tpu._private.lock_sanitizer import tracked_lock
        self._lock = tracked_lock("lineage", reentrant=False)
        #: guarded by self._lock
        self._producers: Dict[ObjectID, Any] = {}  # oid -> TaskSpec
        self._max_entries = max_entries

    def record(self, return_ids: List[ObjectID], spec: Any) -> None:
        with self._lock:
            if len(self._producers) >= self._max_entries:
                return  # lineage cap (reference: max_lineage_bytes)
            for oid in return_ids:
                self._producers[oid] = spec

    def producer_of(self, oid: ObjectID) -> Optional[Any]:
        with self._lock:
            return self._producers.get(oid)

    def release(self, oid: ObjectID) -> None:
        with self._lock:
            spec = self._producers.pop(oid, None)
        # The spec's arg ObjectRefs are dropped OUTSIDE the lock: their
        # __del__ can cascade back into refcounting/lineage.
        del spec

    def num_entries(self) -> int:
        with self._lock:
            return len(self._producers)
