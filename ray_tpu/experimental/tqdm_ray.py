"""Multi-process-safe progress bars (reference:
`python/ray/experimental/tqdm_ray.py` — tqdm-shaped API whose updates
flow to the driver instead of fighting over the terminal)."""

from __future__ import annotations

import sys
import threading
import time
from typing import Any, Dict, Iterable, Optional

_registry: Dict[int, "tqdm"] = {}
_lock = threading.Lock()
_next_id = [0]


class tqdm:
    """Drop-in subset: total/desc/update/close, iteration wrapping."""

    def __init__(self, iterable: Optional[Iterable] = None, *,
                 total: Optional[int] = None, desc: str = "",
                 position: Optional[int] = None, flush_period_s: float = 0.5):
        self.iterable = iterable
        self.total = total if total is not None else (
            len(iterable) if hasattr(iterable, "__len__") else None)
        self.desc = desc
        self.n = 0
        self._last_flush = 0.0
        self.flush_period_s = flush_period_s
        self._closed = False
        with _lock:
            self.bar_id = _next_id[0]
            _next_id[0] += 1
            _registry[self.bar_id] = self

    def update(self, n: int = 1) -> None:
        self.n += n
        now = time.time()
        if now - self._last_flush >= self.flush_period_s:
            self._last_flush = now
            self._render()

    def set_description(self, desc: str) -> None:
        self.desc = desc

    def _render(self) -> None:
        total = f"/{self.total}" if self.total else ""
        sys.stderr.write(f"\r[{self.desc or 'progress'}] "
                         f"{self.n}{total}")
        sys.stderr.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._render()
        sys.stderr.write("\n")
        with _lock:
            _registry.pop(self.bar_id, None)

    def __iter__(self):
        if self.iterable is None:
            raise TypeError("tqdm not given an iterable")
        try:
            for item in self.iterable:
                yield item
                self.update(1)
        finally:
            self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def safe_print(*args, **kwargs) -> None:
    """Print without corrupting progress lines."""
    sys.stderr.write("\n")
    print(*args, **kwargs)
